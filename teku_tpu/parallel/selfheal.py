"""Mesh self-healing: shard-level fault isolation, ejection, reshape.

PR 10 made the mesh the production verify backend but kept the
whole-backend failure semantics of PR 1: one wedged shard trips the
ENTIRE mesh breaker and every verify drops to the host oracle — a
cliff from N-chip device throughput to ~CPU-oracle speed, exactly when
a 1M-validator node can least afford it.  This module makes losing a
chip cost 1/N capacity instead of all of it (ACE Runtime, PAPERS.md:
sub-second cryptographic finality as a *runtime* property that
survives component failure):

- ``DeviceHealthLedger`` — breaker-style per-DEVICE health: every
  mesh dispatch failure is attributed to a device by an isolation
  probe sweep (a collective failure names no chip, so each live
  device answers a tiny deadline-bounded probe; the wedged one can't),
  and ``trip_threshold`` consecutive attributed failures eject it.
- ``MeshHealer`` — the eject → reshape → readmit machine.  On
  ejection it re-plans onto the largest surviving pow-2 device subset
  (``parallel.make_mesh(devices=...)`` + the same group-aligned
  planner), AOT-warms the shrunken sharded shape set OFF the gossip
  path (the loader's warmup machinery), and atomically swaps the
  serving provider: in-flight verifies either complete on the old
  plan or retry on the new one — zero wrong verdicts, zero dropped
  tasks (the PR 1 hot-swap invariant, applied mid-mesh).  A
  background reprobe (the supervisor's half-open-slot idea, extended
  to ejected devices) re-admits a recovered chip and the mesh grows
  back.  The oracle remains the LAST resort, when the mesh shrinks to
  zero healthy devices.

The whole cycle is measured as a recovery-time objective:
``bls_mesh_reshape_total{direction,devices}`` counts every reshape,
``bls_mesh_recovery_seconds`` is the last eject→serving recovery, and
``mesh_eject`` / ``mesh_reshape`` / ``mesh_readmit`` flight-recorder
events carry the triggering dispatch's trace id so the doctor can
name the dispatch that killed a chip.  bench.py's ``chaos`` phase and
the loadgen ``chaos_device_loss`` scenario drive this REAL machinery
(faults keyed by device index at the ``bls.mesh_shard`` site), and
tools/bench_diff.py gates recovery ≤ ``mesh_recovery_s_max`` with
zero wrong verdicts and zero protected-class sheds.

The healer is deliberately GENERIC over the backend world: production
wires jax devices + ``JaxBls12381(mesh=...)`` factories
(crypto/bls/loader.py), the loadgen chaos scenario wires model devices
on a virtual clock — same ledger, same reshape state machine, same
events, so the control plane under chaos test IS the production code.
"""

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..infra import flightrecorder, timeline, tracing
from ..infra.env import env_float, env_int
from ..infra.metrics import GLOBAL_REGISTRY, MetricsRegistry
from ..infra.pow2 import floor_pow2 as _floor_pow2

_LOG = logging.getLogger(__name__)

# the keyed fault site shared by the collective dispatch (keys = the
# live device index set) and the per-device isolation probes (keys =
# one index) — see infra/faults.py
FAULT_SITE = "bls.mesh_shard"

# closed {direction} vocabulary of the reshape counter (linted)
DIRECTIONS = ("shrink", "grow")

# Shared readout for the supplier gauges: one process serves one mesh,
# so (like parallel._ACTIVE) the most recent healer activity is the
# truthful value even when tests construct several healers.
_STATE = {"recovery_s": 0.0, "ejected": 0, "live": 0, "configured": 0}

GLOBAL_REGISTRY.gauge(
    "bls_mesh_recovery_seconds",
    "wall seconds of the last completed mesh recovery (dispatch "
    "failure -> reshaped mesh serving); 0 = no recovery yet",
    supplier=lambda: float(_STATE["recovery_s"]))
GLOBAL_REGISTRY.gauge(
    "bls_mesh_ejected_devices",
    "devices currently ejected from the verify mesh by the "
    "self-healing ledger",
    supplier=lambda: float(_STATE["ejected"]))
# the reshape family registers at import (complete from scrape 1, and
# the exposition lint can assert its label contract without needing a
# healer built); per-healer instances get_or_create the same family
GLOBAL_REGISTRY.labeled_counter(
    "bls_mesh_reshape_total",
    "self-healing mesh reshapes by direction (shrink = device "
    "ejected, grow = device readmitted) and the NEW live device count",
    labelnames=("direction", "devices"))


class InstallVetoError(RuntimeError):
    """Raised by a reshape-warm hook to VETO installing the reshaped
    backend: the surviving subset executed but produced a wrong
    verdict on known input (the loader maps WarmupVetoError here).
    Correctness over capacity, always — the old pair keeps serving
    and its breaker owns containment."""


def trip_threshold_default() -> int:
    """Consecutive ATTRIBUTED failures (dispatch failure + failed
    isolation probe) before a device is ejected.  Default 1: an
    ejection already requires two independent pieces of evidence."""
    return max(1, env_int("TEKU_TPU_MESH_DEVICE_TRIP", 1))


def probe_deadline_default() -> float:
    return max(0.1, env_float("TEKU_TPU_MESH_PROBE_DEADLINE_S", 5.0))


def reprobe_interval_default() -> float:
    return max(0.05, env_float("TEKU_TPU_MESH_REPROBE_S", 15.0))


class DeviceHealthLedger:
    """Per-device breaker-style health accounting for one mesh.

    Devices are addressed by index into the CONFIGURED (boot-time)
    device list; ``live()``/``ejected()`` return indices in that
    original order so the reshape's "largest surviving pow-2 subset"
    is deterministic.  Thread-safe: failures arrive from breaker
    dispatch threads, probes from the heal thread, readmits from the
    reprobe thread."""

    LIVE, EJECTED = "live", "ejected"

    def __init__(self, device_names: Sequence[str],
                 trip_threshold: Optional[int] = None):
        self.device_names = [str(d) for d in device_names]
        self.trip_threshold = (trip_threshold
                               if trip_threshold is not None
                               else trip_threshold_default())
        self._lock = threading.Lock()
        n = len(self.device_names)
        self._state = [self.LIVE] * n
        self._consecutive = [0] * n
        self._failures = [0] * n
        self._ejects = [0] * n
        self._last_error = [""] * n

    def record_failure(self, idx: int, error: str = "") -> bool:
        """One attributed failure; True when it crossed the trip
        threshold (the caller should eject)."""
        with self._lock:
            self._consecutive[idx] += 1
            self._failures[idx] += 1
            self._last_error[idx] = str(error)[:200]
            return (self._state[idx] == self.LIVE
                    and self._consecutive[idx] >= self.trip_threshold)

    def record_success(self, idx: int) -> None:
        with self._lock:
            self._consecutive[idx] = 0

    def eject(self, idx: int, count: bool = True) -> bool:
        """``count=False`` is the readmit-ROLLBACK path (a grow
        reshape that failed to install): the device goes back to
        ejected without inflating its eject count — a failed install
        is not a new flap."""
        with self._lock:
            if self._state[idx] == self.EJECTED:
                return False
            self._state[idx] = self.EJECTED
            if count:
                self._ejects[idx] += 1
            return True

    def readmit(self, idx: int) -> bool:
        with self._lock:
            if self._state[idx] == self.LIVE:
                return False
            self._state[idx] = self.LIVE
            self._consecutive[idx] = 0
            return True

    def live(self) -> List[int]:
        with self._lock:
            return [i for i, s in enumerate(self._state)
                    if s == self.LIVE]

    def ejected(self) -> List[int]:
        with self._lock:
            return [i for i, s in enumerate(self._state)
                    if s == self.EJECTED]

    def eject_count(self, idx: int) -> int:
        with self._lock:
            return self._ejects[idx]

    def snapshot(self) -> dict:
        with self._lock:
            return {"devices": [
                {"index": i, "name": self.device_names[i],
                 "state": self._state[i],
                 "consecutive_failures": self._consecutive[i],
                 "failures_total": self._failures[i],
                 "ejects_total": self._ejects[i],
                 "last_error": self._last_error[i]}
                for i in range(len(self.device_names))],
                "trip_threshold": self.trip_threshold}


class MeshHealer:
    """Eject → reshape → readmit over a pluggable backend world.

    - ``probe(index)`` (thread context, deadline-bounded by the
      healer) proves device `index` executes; raises/hangs when sick.
      Production probes run a tiny computation placed on the device;
      both worlds consult ``faults.check(FAULT_SITE, keys=(index,))``
      so the chaos harness can wedge exactly one chip.
    - ``make_backend(live_indices)`` builds a provider for the pow-2
      live subset (len >= 2: a sharded mesh; len == 1: single-device;
      empty tuple -> return None, oracle is the last resort).
    - ``warm(backend, live_indices)`` (optional) AOT-compiles the new
      shape set OFF the serving path; exceptions install anyway (the
      first real batch compiles lazily — same rule as supervisor
      warmup).
    - ``install(backend, live_indices, epoch)`` atomically swaps the
      serving provider (``GuardedBls12381.swap_device``) and updates
      the readiness surfaces.  Called with ``backend=None`` when the
      mesh shrank to zero — the caller keeps the oracle serving.
    """

    def __init__(self, device_names: Sequence[str],
                 probe: Callable[[int], None],
                 make_backend: Callable[[Tuple[int, ...]], object],
                 install: Callable[[object, Tuple[int, ...], int], None],
                 warm: Optional[Callable] = None,
                 trip_threshold: Optional[int] = None,
                 probe_deadline_s: Optional[float] = None,
                 reprobe_s: Optional[float] = None,
                 min_mesh: int = 2,
                 name: str = "bls_mesh",
                 registry: MetricsRegistry = GLOBAL_REGISTRY,
                 recorder: Optional[flightrecorder.FlightRecorder]
                 = None):
        self.name = name
        self.probe = probe
        self.make_backend = make_backend
        self.install = install
        self.warm = warm
        self.min_mesh = min_mesh
        self.trip_threshold = (trip_threshold
                               if trip_threshold is not None
                               else trip_threshold_default())
        self.probe_deadline_s = (probe_deadline_s
                                 if probe_deadline_s is not None
                                 else probe_deadline_default())
        self.reprobe_s = (reprobe_s if reprobe_s is not None
                          else reprobe_interval_default())
        self.ledger = DeviceHealthLedger(device_names,
                                         self.trip_threshold)
        self.configured_n = len(self.ledger.device_names)
        self.epoch = 0
        self.last_recovery_s: Optional[float] = None
        self.reshapes = {d: 0 for d in DIRECTIONS}
        self._recorder = recorder or flightrecorder.RECORDER
        self._live: Tuple[int, ...] = tuple(
            range(self.configured_n))
        self._lock = threading.Lock()       # heal single-flight state
        self._reshape_lock = threading.Lock()
        self._healing = False
        # failure contexts queued while a heal is in flight
        self._pending: List[Tuple[str, bool, Optional[str]]] = []
        self._closed = False
        self._reprobe_thread: Optional[threading.Thread] = None
        self._m_reshape = registry.labeled_counter(
            "bls_mesh_reshape_total",
            "self-healing mesh reshapes by direction (shrink = device "
            "ejected, grow = device readmitted) and the NEW live "
            "device count",
            labelnames=("direction", "devices"))
        _STATE["configured"] = self.configured_n
        _STATE["live"] = self.configured_n
        _STATE["ejected"] = 0

    # ------------------------------------------------------------------
    @property
    def live_devices(self) -> Tuple[int, ...]:
        return self._live

    def close(self) -> None:
        self._closed = True

    def snapshot(self) -> dict:
        """JSON-able state for the supervisor's readiness snapshot."""
        return {"configured": self.configured_n,
                "live": len(self._live),
                "live_devices": [self.ledger.device_names[i]
                                 for i in self._live],
                "ejected": [self.ledger.device_names[i]
                            for i in self.ledger.ejected()],
                "epoch": self.epoch,
                "reshapes": dict(self.reshapes),
                "last_recovery_s": self.last_recovery_s,
                "trip_threshold": self.trip_threshold,
                "reprobe_s": self.reprobe_s}

    # ------------------------------------------------------------------
    def on_dispatch_failure(self, error: str = "",
                            timeout: bool = False,
                            trace_id: Optional[str] = None) -> None:
        """A mesh dispatch failed/overran: attribute it to a device in
        a background heal thread (single-flight; failures arriving
        mid-heal queue ONE follow-up sweep).  Never blocks or raises —
        it is called from the guarded dispatch's failure path, where
        the oracle is already serving the caller."""
        if self._closed:
            return
        if trace_id is None:
            trace_id = (tracing.current_trace_id()
                        or self._recorder.last_trace_id())
        with self._lock:
            if self._healing:
                # queue THIS failure's context: the follow-up sweep's
                # eject events must cite a dispatch that actually
                # failed during the heal, not replay the first one's
                self._pending.append((error, timeout, trace_id))
                return
            self._healing = True
        threading.Thread(
            target=self._heal_loop, args=(error, timeout, trace_id),
            daemon=True, name=f"{self.name}-heal").start()

    def _heal_loop(self, error, timeout, trace_id) -> None:
        try:
            while True:
                self._heal_once(error, timeout, trace_id)
                with self._lock:
                    if not self._pending:
                        self._healing = False
                        return
                    # the most recent failure's context drives the
                    # follow-up sweep (overlapping failures collapse
                    # to one sweep; its events cite the latest)
                    error, timeout, trace_id = self._pending[-1]
                    self._pending.clear()
        except Exception:  # pragma: no cover - heal must never crash
            _LOG.exception("mesh heal failed")
            with self._lock:
                self._healing = False

    def _heal_once(self, error, timeout, trace_id) -> None:
        t0 = time.monotonic()
        live = self.ledger.live()
        if not live:
            return
        verdicts = self._probe_devices(live)
        tripped = []
        for idx in live:
            err = verdicts.get(idx)
            if err is None:
                self.ledger.record_success(idx)
            elif self.ledger.record_failure(idx, err):
                tripped.append((idx, err))
        if not tripped:
            # unattributable collective failure (e.g. host-side): the
            # whole-backend breaker keeps owning it — defense in depth
            self._recorder.record(
                "mesh_heal_unattributed", trace_id=trace_id,
                healer=self.name, probed=len(live),
                dispatch_error=str(error)[:200],
                dispatch_timeout=timeout)
            return
        for idx, err in tripped:
            self.ledger.eject(idx)
            _STATE["ejected"] = len(self.ledger.ejected())
            _LOG.warning(
                "mesh device %s EJECTED (%s; dispatch failure: %s)",
                self.ledger.device_names[idx], err,
                error or ("deadline overrun" if timeout else "?"))
            self._recorder.record(
                "mesh_eject", trace_id=trace_id, healer=self.name,
                device=self.ledger.device_names[idx], index=idx,
                probe_error=err, dispatch_error=str(error)[:200],
                dispatch_timeout=timeout,
                eject_count=self.ledger.eject_count(idx))
            # mesh overlay track on the causal timeline
            timeline.instant(
                "mesh", "eject", trace_id=trace_id,
                device=self.ledger.device_names[idx])
        try:
            self._reshape("shrink", recovery_t0=t0, trace_id=trace_id)
        finally:
            # ejected devices must ALWAYS end up watched, even when
            # the reshape itself raised (make_backend/install
            # hiccup): the reprobe loop also RECONCILES the live set
            # on its next tick, so a failed shrink install is retried
            # instead of stranding the wedged full-width mesh
            self._ensure_reprobe()

    def _probe_devices(self, idxs: Sequence[int]) -> Dict[int, Optional[str]]:
        """Deadline-bounded isolation probes, all devices in parallel
        (a wedged device must cost ONE deadline, not one per chip).
        Returns {index: None (healthy) | error string}."""
        boxes: Dict[int, dict] = {i: {} for i in idxs}
        events: Dict[int, threading.Event] = {
            i: threading.Event() for i in idxs}

        def run(i):
            try:
                self.probe(i)
            except BaseException as exc:  # noqa: BLE001 - verdict
                boxes[i]["err"] = f"{type(exc).__name__}: {exc}"
            finally:
                events[i].set()

        for i in idxs:
            threading.Thread(target=run, args=(i,), daemon=True,
                             name=f"{self.name}-probe-{i}").start()
        deadline = time.monotonic() + self.probe_deadline_s
        out: Dict[int, Optional[str]] = {}
        for i in idxs:
            if not events[i].wait(max(deadline - time.monotonic(),
                                      0.001)):
                out[i] = (f"probe overran "
                          f"{self.probe_deadline_s:.1f}s deadline")
            else:
                out[i] = boxes[i].get("err")
        return out

    # ------------------------------------------------------------------
    def _desired_live(self) -> Tuple[int, ...]:
        """The live subset the mesh SHOULD be serving: the largest
        pow-2 prefix of the healthy devices (one chip single-device,
        zero = oracle).  ONE definition — the reshape targets it and
        the reprobe loop reconciles the installed set against it."""
        healthy = self.ledger.live()
        n = _floor_pow2(len(healthy)) if healthy else 0
        if n < self.min_mesh:
            # below the smallest shardable mesh: one healthy chip
            # still serves single-device; zero means the oracle is
            # the last resort (install(None) — caller keeps it)
            n = 1 if healthy else 0
        return tuple(healthy[:n])

    def _reshape(self, direction: str, recovery_t0: Optional[float]
                 = None, trace_id: Optional[str] = None) -> bool:
        """Re-plan onto the largest surviving pow-2 subset, AOT-warm
        it off-path, and atomically install.  Serialized: a shrink and
        a concurrent readmit-grow must not interleave installs.
        Returns True when the install happened (False = vetoed; the
        reprobe loop rolls a failed grow's readmits back)."""
        with self._reshape_lock:
            if self._closed:
                return False
            t0 = recovery_t0 if recovery_t0 is not None \
                else time.monotonic()
            old_n = len(self._live)
            live = self._desired_live()
            n = len(live)
            backend = self.make_backend(live) if n else None
            if backend is not None and self.warm is not None:
                try:
                    # AOT warm OFF the serving path: the shrunken
                    # sharded shape set compiles here, not inside a
                    # breaker-guarded live dispatch
                    self.warm(backend, live)
                except InstallVetoError as exc:
                    # wrong verdict on known input: never install —
                    # the old pair keeps serving under its breaker
                    _LOG.error(
                        "mesh reshape to %d device(s) VETOED "
                        "(untrusted verdicts): %s", n, exc)
                    self._recorder.record(
                        "mesh_reshape_vetoed", trace_id=trace_id,
                        healer=self.name, direction=direction,
                        to_devices=n, error=str(exc)[:200])
                    return False
                except Exception:
                    _LOG.exception(
                        "mesh reshape warmup failed; installing "
                        "anyway (first real batch compiles lazily)")
            if self._closed:
                # the owner closed the healer while the candidate was
                # warming (a multi-minute compile): installing now
                # would mutate global serving state — gauge, readiness
                # mesh, latency-series retirement — that the close was
                # supposed to fence off (e.g. after supervisor
                # uninstall, or bench's chaos phase handing the
                # process to later phases)
                _LOG.info("mesh healer closed mid-reshape; candidate "
                          "discarded")
                return False
            self.epoch += 1
            self.install(backend, live, self.epoch)
            self._live = live
            self.reshapes[direction] = \
                self.reshapes.get(direction, 0) + 1
            dt = time.monotonic() - t0
            self._m_reshape.labels(direction=direction,
                                   devices=str(n)).inc()
            _STATE["live"] = n
            _STATE["ejected"] = len(self.ledger.ejected())
            if direction == "shrink":
                self.last_recovery_s = round(dt, 3)
                _STATE["recovery_s"] = self.last_recovery_s
            _LOG.warning(
                "mesh reshaped (%s): %d -> %d device(s) of %d "
                "configured, epoch %d, %.3fs", direction, old_n, n,
                self.configured_n, self.epoch, dt)
            self._recorder.record(
                "mesh_reshape", trace_id=trace_id, healer=self.name,
                direction=direction, from_devices=old_n,
                to_devices=n, configured=self.configured_n,
                epoch=self.epoch, recovery_s=round(dt, 3))
            # mesh-heal interval on the causal timeline: the duration
            # rides alone (the healer's stopwatch is time.monotonic —
            # a different base than the spine's mono axis, so the
            # interval is placed by its END, never by subtracting
            # across clock bases)
            timeline.interval(
                "mesh", "reshape", dt, trace_id=trace_id,
                direction=direction, devices=n,
                epoch=self.epoch)
            return True

    # ------------------------------------------------------------------
    def _ensure_reprobe(self) -> None:
        with self._lock:
            t = self._reprobe_thread
            if t is not None and t.is_alive():
                return
            self._reprobe_thread = threading.Thread(
                target=self._reprobe_loop, daemon=True,
                name=f"{self.name}-reprobe")
            self._reprobe_thread.start()

    def _reprobe_loop(self) -> None:
        """The supervisor's background-reprobe idea extended to
        ejected devices: probe them on an interval; a recovered chip
        re-admits and the mesh grows back.  The loop also RECONCILES
        the installed live set against the desired one, so a reshape
        whose install previously failed or vetoed gets retried here
        instead of stranding the mesh.  The thread exits only when
        nothing is ejected AND the install matches — and decides that
        under the same lock ``_ensure_reprobe`` takes, so an eject
        landing between the check and the exit finds
        ``_reprobe_thread`` cleared and starts a fresh thread
        (TOCTOU)."""
        while not self._closed:
            time.sleep(self.reprobe_s)
            if self._closed:
                return
            with self._lock:
                if not self.ledger.ejected() \
                        and self._desired_live() == self._live:
                    self._reprobe_thread = None
                    return
            ejected = self.ledger.ejected()
            t0 = time.monotonic()
            readmitted = []
            if ejected:
                verdicts = self._probe_devices(ejected)
                for idx in ejected:
                    if verdicts.get(idx) is None:
                        self.ledger.readmit(idx)
                        readmitted.append(idx)
                        _LOG.info("mesh device %s READMITTED",
                                  self.ledger.device_names[idx])
                        self._recorder.record(
                            "mesh_readmit", healer=self.name,
                            device=self.ledger.device_names[idx],
                            index=idx)
            desired = self._desired_live()
            if readmitted or desired != self._live:
                direction = ("grow" if len(desired) >= len(self._live)
                             else "shrink")
                installed = False
                try:
                    installed = self._reshape(direction,
                                              recovery_t0=t0)
                except Exception:  # pragma: no cover - keep probing
                    _LOG.exception("mesh %s reshape failed",
                                   direction)
                if not installed and readmitted:
                    # the grow did NOT install (veto / transient
                    # failure): roll the readmits back so the
                    # shrunken-but-serving state stays truthful
                    # (ledger, gauges, recovered=...) and this loop
                    # RETRIES instead of exiting with the mesh
                    # silently stuck below width.  count=False — a
                    # failed install is not a new flap.
                    for idx in readmitted:
                        self.ledger.eject(idx, count=False)
                    _STATE["ejected"] = len(self.ledger.ejected())
