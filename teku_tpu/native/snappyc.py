"""Snappy codec binding: C++ implementation with pure-Python fallback.

The ssz_snappy framing codec (reference: networking/eth2 gossip
SszSnappyEncoding + snappy-java).  The Python fallback decompresses the
full format and compresses as all-literals — spec-valid output, zero
ratio, guaranteed correct.
"""

import ctypes
from typing import Optional

from . import get_lib

MAX_UNCOMPRESSED = 1 << 27        # 128 MiB safety bound


class SnappyError(ValueError):
    pass


def compress(data: bytes) -> bytes:
    lib = get_lib()
    if lib is not None:
        cap = lib.teku_snappy_max_compressed(len(data))
        out = ctypes.create_string_buffer(cap)
        n = lib.teku_snappy_compress(data, len(data), out)
        if n == 0 and data:
            raise SnappyError("compress failed")
        return out.raw[:n]
    return _py_compress(data)


def uncompress(data: bytes) -> bytes:
    lib = get_lib()
    if lib is not None:
        want = ctypes.c_uint64()
        if lib.teku_snappy_uncompressed_length(data, len(data),
                                               ctypes.byref(want)):
            raise SnappyError("bad varint header")
        if want.value > MAX_UNCOMPRESSED:
            raise SnappyError("declared size too large")
        out = ctypes.create_string_buffer(max(1, want.value))
        n = lib.teku_snappy_uncompress(data, len(data), out, want.value)
        if n == 2 ** 64 - 1:
            raise SnappyError("malformed snappy input")
        return out.raw[:n]
    return _py_uncompress(data)


# -- pure-Python fallback ---------------------------------------------------

def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _py_compress(data: bytes) -> bytes:
    """All-literal encoding: valid snappy, no compression."""
    out = bytearray(_varint(len(data)))
    pos = 0
    while pos < len(data):
        chunk = data[pos:pos + 65536]
        l = len(chunk) - 1
        if l < 60:
            out.append(l << 2)
        else:
            out.append(61 << 2)
            out += l.to_bytes(2, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)


def _py_uncompress(data: bytes) -> bytes:
    pos = 0
    expect = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint")
        b = data[pos]
        pos += 1
        expect |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
        if shift > 63:
            raise SnappyError("varint overflow")
    if expect > MAX_UNCOMPRESSED:
        raise SnappyError("declared size too large")
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:
            length = (tag >> 2) + 1
            if length > 60:
                extra = (tag >> 2) - 59     # 60->1, 61->2, 62->3 bytes
                if pos + extra > len(data):
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(data[pos:pos + extra],
                                        "little") + 1
                pos += extra
            if pos + length > len(data):
                raise SnappyError("truncated literal")
            if len(out) + length > expect:
                raise SnappyError("output exceeds declared size")
            out += data[pos:pos + length]
            pos += length
        else:
            if kind == 1:
                if pos >= len(data):
                    raise SnappyError("truncated copy")
                length = ((tag >> 2) & 0x7) + 4
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:
                if pos + 2 > len(data):
                    raise SnappyError("truncated copy")
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos:pos + 2], "little")
                pos += 2
            else:
                if pos + 4 > len(data):
                    raise SnappyError("truncated copy")
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos:pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise SnappyError("bad copy offset")
            if len(out) + length > expect:
                raise SnappyError("output exceeds declared size")
            for _ in range(length):
                out.append(out[-offset])
    if len(out) != expect:
        raise SnappyError("length mismatch")
    return bytes(out)
