"""Native (C++) components: SHA-256 hashing + KV engine.

Where the reference leans on native code — JVM SHA-256 intrinsics for
merkleization and rocksdbjni/leveldb-native for storage (reference:
gradle/versions.gradle:128-131) — this package builds a small C++
library (SHA-NI accelerated hashing, append-log KV engine) on demand
with the system toolchain and binds it via ctypes.  Everything has a
pure-Python fallback so the framework still runs where no compiler
exists.
"""

import ctypes
import logging
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

from ..infra.env import env_str

_LOG = logging.getLogger(__name__)

_SRC = Path(__file__).parent / "src"
_LIB_NAME = "libteku_native.so"


def _build(out_dir: Path) -> Optional[Path]:
    out = out_dir / _LIB_NAME
    srcs = [str(_SRC / "sha256.cpp"), str(_SRC / "kvstore.cpp"),
            str(_SRC / "snappy.cpp")]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if out.is_file() and os.path.getmtime(out) >= newest_src:
        return out
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", str(out)] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return out
    except Exception as exc:  # pragma: no cover - toolchain missing
        _LOG.warning("native build failed (%s); using pure-Python "
                     "fallbacks", exc)
        return None


_lib: Optional[ctypes.CDLL] = None
_tried = False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    build_dir = Path(env_str("TEKU_TPU_NATIVE_DIR")
                     or Path(__file__).parent / "build")
    try:
        build_dir.mkdir(parents=True, exist_ok=True)
        path = _build(build_dir)
        if path is None:
            return None
        lib = ctypes.CDLL(str(path))
        lib.teku_hash_pairs.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_char_p]
        lib.teku_sha256.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                    ctypes.c_char_p]
        lib.teku_sha_uses_shani.restype = ctypes.c_int
        lib.kv_open.argtypes = [ctypes.c_char_p]
        lib.kv_open.restype = ctypes.c_void_p
        lib.kv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint32, ctypes.c_char_p,
                               ctypes.c_uint32]
        lib.kv_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint32]
        lib.kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint32,
                               ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                               ctypes.POINTER(ctypes.c_uint32)]
        lib.kv_keys.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32,
                                ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                                ctypes.POINTER(ctypes.c_uint64)]
        lib.kv_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
        lib.kv_count.argtypes = [ctypes.c_void_p]
        lib.kv_count.restype = ctypes.c_uint64
        lib.kv_flush.argtypes = [ctypes.c_void_p]
        lib.kv_compact.argtypes = [ctypes.c_void_p]
        lib.kv_close.argtypes = [ctypes.c_void_p]
        lib.teku_snappy_max_compressed.argtypes = [ctypes.c_uint64]
        lib.teku_snappy_max_compressed.restype = ctypes.c_uint64
        lib.teku_snappy_compress.argtypes = [ctypes.c_char_p,
                                             ctypes.c_uint64,
                                             ctypes.c_char_p]
        lib.teku_snappy_compress.restype = ctypes.c_uint64
        lib.teku_snappy_uncompress.argtypes = [ctypes.c_char_p,
                                               ctypes.c_uint64,
                                               ctypes.c_char_p,
                                               ctypes.c_uint64]
        lib.teku_snappy_uncompress.restype = ctypes.c_uint64
        lib.teku_snappy_uncompressed_length.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.teku_snappy_uncompressed_length.restype = ctypes.c_int
        lib.teku_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.teku_crc32c.restype = ctypes.c_uint32
        _lib = lib
        _LOG.info("native library loaded (sha-ni=%s)",
                  bool(lib.teku_sha_uses_shani()))
    except Exception as exc:  # pragma: no cover
        _LOG.warning("native load failed: %s", exc)
        _lib = None
    return _lib
