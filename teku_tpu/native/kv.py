"""KV database binding: C++ engine via ctypes, pure-Python fallback.

Both implementations speak the SAME on-disk append-log format (op,
lengths, payload, CRC32), so a database written by one opens under the
other — which the tests exploit as a cross-implementation conformance
check.  The role of the reference's rocksdb/leveldb storage server
(reference: storage/.../server/kvstore/).
"""

import ctypes
import struct
import zlib
from pathlib import Path
from typing import Iterator, List, Optional, Union

from . import get_lib

_OP_PUT, _OP_DEL = 1, 2


class KvStore:
    """dict-like persistent store; explicit flush/compact/close."""

    def __new__(cls, path):
        lib = get_lib()
        if cls is KvStore and lib is not None:
            inst = object.__new__(_NativeKv)
        else:
            inst = object.__new__(
                _PythonKv if cls is KvStore else cls)
        return inst

    # interface ---------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def keys_with_prefix(self, prefix: bytes = b"") -> List[bytes]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def compact(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _NativeKv(KvStore):
    def __init__(self, path):
        self._lib = get_lib()
        self._h = self._lib.kv_open(str(path).encode())
        if not self._h:
            raise OSError(f"kv_open failed for {path}")

    def get(self, key: bytes) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_ubyte)()
        out_len = ctypes.c_uint32()
        rc = self._lib.kv_get(self._h, key, len(key),
                              ctypes.byref(out), ctypes.byref(out_len))
        if rc == 1:
            return None
        if rc != 0:
            raise OSError("kv_get: log read failed")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kv_free(out)

    def put(self, key: bytes, value: bytes) -> None:
        if self._lib.kv_put(self._h, key, len(key), value, len(value)):
            raise OSError("kv_put failed")

    def delete(self, key: bytes) -> None:
        if self._lib.kv_del(self._h, key, len(key)) < 0:
            raise OSError("kv_del failed")

    def keys_with_prefix(self, prefix: bytes = b"") -> List[bytes]:
        out = ctypes.POINTER(ctypes.c_ubyte)()
        out_len = ctypes.c_uint64()
        self._lib.kv_keys(self._h, prefix, len(prefix),
                          ctypes.byref(out), ctypes.byref(out_len))
        try:
            blob = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kv_free(out)
        keys, pos = [], 0
        while pos < len(blob):
            (n,) = struct.unpack_from("<I", blob, pos)
            pos += 4
            keys.append(blob[pos:pos + n])
            pos += n
        return keys

    def __len__(self) -> int:
        return self._lib.kv_count(self._h)

    def flush(self) -> None:
        if self._lib.kv_flush(self._h):
            raise OSError("kv_flush failed")

    def compact(self) -> None:
        if self._lib.kv_compact(self._h):
            raise OSError("kv_compact failed")

    def close(self) -> None:
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None


class _PythonKv(KvStore):
    """Same format, pure Python (no toolchain / cross-checks)."""

    def __init__(self, path):
        self._path = Path(path)
        self._index = {}
        good_end = 0
        if self._path.is_file():
            data = self._path.read_bytes()
            pos = 0
            while pos + 9 <= len(data):
                op, klen, vlen = struct.unpack_from("<BII", data, pos)
                end = pos + 9 + klen + vlen + 4
                if (op not in (_OP_PUT, _OP_DEL) or klen > 1 << 30
                        or vlen > 1 << 30 or end > len(data)):
                    break
                (want,) = struct.unpack_from("<I", data, end - 4)
                if zlib.crc32(data[pos:end - 4]) != want:
                    break
                key = data[pos + 9:pos + 9 + klen]
                if op == _OP_PUT:
                    self._index[key] = data[pos + 9 + klen:end - 4]
                else:
                    self._index.pop(key, None)
                pos = end
            good_end = pos
            if good_end < len(data):   # torn tail
                with open(self._path, "r+b") as f:
                    f.truncate(good_end)
        self._log = open(self._path, "ab")

    def _append(self, op: int, key: bytes, value: bytes) -> None:
        rec = struct.pack("<BII", op, len(key), len(value)) + key + value
        rec += struct.pack("<I", zlib.crc32(rec))
        self._log.write(rec)

    def get(self, key: bytes) -> Optional[bytes]:
        return self._index.get(key)

    def put(self, key: bytes, value: bytes) -> None:
        self._append(_OP_PUT, key, value)
        self._index[key] = value

    def delete(self, key: bytes) -> None:
        if key in self._index:
            self._append(_OP_DEL, key, b"")
            del self._index[key]

    def keys_with_prefix(self, prefix: bytes = b"") -> List[bytes]:
        return sorted(k for k in self._index if k.startswith(prefix))

    def __len__(self) -> int:
        return len(self._index)

    def flush(self) -> None:
        self._log.flush()
        import os
        os.fsync(self._log.fileno())

    def compact(self) -> None:
        tmp = self._path.with_suffix(".compact")
        old_log = self._log
        with open(tmp, "wb") as f:
            for k in sorted(self._index):
                v = self._index[k]
                rec = struct.pack("<BII", _OP_PUT, len(k), len(v)) + k + v
                rec += struct.pack("<I", zlib.crc32(rec))
                f.write(rec)
            f.flush()
            import os
            os.fsync(f.fileno())
        old_log.close()
        tmp.replace(self._path)
        self._log = open(self._path, "ab")

    def close(self) -> None:
        if self._log:
            self._log.flush()
            self._log.close()
            self._log = None
