"""Bulk SHA-256 pair hashing for merkleization (native-backed)."""

from typing import List, Optional

from . import get_lib

_lib = None
_checked = False


import ctypes


def _native():
    global _lib, _checked
    if not _checked:
        _checked = True
        lib = get_lib()
        if lib is not None:
            # self-check against hashlib before trusting the fast path
            import hashlib
            probe = bytes(range(64))
            out = ctypes.create_string_buffer(32)
            lib.teku_hash_pairs(probe, 1, out)
            if out.raw == hashlib.sha256(probe).digest():
                _lib = lib
    return _lib


def hash_pairs(level: List[bytes]) -> List[bytes]:
    """[sha256(level[2i] + level[2i+1])] — one native call per level."""
    lib = _native()
    n = len(level) // 2
    if lib is None:
        import hashlib
        return [hashlib.sha256(level[2 * i] + level[2 * i + 1]).digest()
                for i in range(n)]
    buf = b"".join(level)
    out = ctypes.create_string_buffer(32 * n)
    lib.teku_hash_pairs(buf, n, out)
    raw = out.raw
    return [raw[32 * i:32 * (i + 1)] for i in range(n)]
