// Snappy raw-block format codec (compress + uncompress).
//
// The gossip/req-resp framing codec role snappy-java plays for the
// reference (reference: gradle/versions.gradle:140, used by
// networking/eth2 gossip SszSnappyEncoding and rpc encodings).
// Standard format: varint uncompressed length, then literal elements
// (tag&3==0) and copy elements with 1/2/4-byte offsets.  Compression
// is greedy with a 4-byte-hash match table — not byte-identical to
// upstream snappy output, but format-valid, which is all the format
// requires.

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t load32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash4(uint32_t v) { return (v * 0x1e35a7bdu) >> 18; }  // 14-bit

size_t emit_varint(uint8_t* out, uint64_t v) {
  size_t n = 0;
  while (v >= 0x80) {
    out[n++] = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  out[n++] = (uint8_t)v;
  return n;
}

size_t emit_literal(uint8_t* out, const uint8_t* data, size_t len) {
  size_t n = 0;
  if (len == 0) return 0;
  size_t l = len - 1;
  if (l < 60) {
    out[n++] = (uint8_t)(l << 2);
  } else if (l < 256) {
    out[n++] = 60 << 2;
    out[n++] = (uint8_t)l;
  } else if (l < 65536) {
    out[n++] = 61 << 2;
    out[n++] = (uint8_t)l;
    out[n++] = (uint8_t)(l >> 8);
  } else {
    out[n++] = 62 << 2;
    out[n++] = (uint8_t)l;
    out[n++] = (uint8_t)(l >> 8);
    out[n++] = (uint8_t)(l >> 16);
  }
  memcpy(out + n, data, len);
  return n + len;
}

size_t emit_copy(uint8_t* out, size_t offset, size_t len) {
  size_t n = 0;
  // prefer 2-byte-offset copies (len 1..64, offset < 65536)
  while (len > 0) {
    size_t chunk = len > 64 ? 64 : len;
    if (chunk < 4) chunk = len;  // tail shorter than 4 uses copy-2 too
    if (chunk >= 4 && chunk <= 11 && offset < 2048) {
      out[n++] = (uint8_t)(1 | ((chunk - 4) << 2) | ((offset >> 8) << 5));
      out[n++] = (uint8_t)offset;
    } else {
      out[n++] = (uint8_t)(2 | ((chunk - 1) << 2));
      out[n++] = (uint8_t)offset;
      out[n++] = (uint8_t)(offset >> 8);
    }
    len -= chunk;
  }
  return n;
}

}  // namespace

extern "C" {

uint64_t teku_snappy_max_compressed(uint64_t n) {
  return 32 + n + n / 6;
}

// returns compressed size, or 0 on error
uint64_t teku_snappy_compress(const uint8_t* in, uint64_t n, uint8_t* out) {
  size_t pos = emit_varint(out, n);
  if (n == 0) return pos;
  static thread_local int32_t table[1 << 14];
  memset(table, -1, sizeof(table));
  size_t ip = 0, lit_start = 0;
  while (ip + 4 <= n) {
    uint32_t h = hash4(load32(in + ip));
    int32_t cand = table[h];
    table[h] = (int32_t)ip;
    if (cand >= 0 && ip - (size_t)cand < 65536 &&
        load32(in + cand) == load32(in + ip)) {
      // flush pending literal
      pos += emit_literal(out + pos, in + lit_start, ip - lit_start);
      // extend the match
      size_t len = 4;
      while (ip + len < n && in[cand + len] == in[ip + len] && len < 1 << 16)
        len++;
      pos += emit_copy(out + pos, ip - cand, len);
      ip += len;
      lit_start = ip;
    } else {
      ip++;
    }
  }
  pos += emit_literal(out + pos, in + lit_start, n - lit_start);
  return pos;
}

// 0 on success; fills *out_n with the declared uncompressed size
int teku_snappy_uncompressed_length(const uint8_t* in, uint64_t n,
                                    uint64_t* out_n) {
  uint64_t v = 0;
  int shift = 0;
  for (uint64_t i = 0; i < n && i < 10; i++) {
    v |= (uint64_t)(in[i] & 0x7F) << shift;
    if (!(in[i] & 0x80)) {
      *out_n = v;
      return 0;
    }
    shift += 7;
  }
  return -1;
}

// returns uncompressed size, or (uint64_t)-1 on malformed input
uint64_t teku_snappy_uncompress(const uint8_t* in, uint64_t n, uint8_t* out,
                                uint64_t cap) {
  uint64_t expect = 0, ip = 0;
  int shift = 0;
  for (;;) {
    if (ip >= n) return (uint64_t)-1;
    uint8_t b = in[ip++];
    expect |= (uint64_t)(b & 0x7F) << shift;
    shift += 7;
    if (!(b & 0x80)) break;
    if (shift > 63) return (uint64_t)-1;
  }
  if (expect > cap) return (uint64_t)-1;
  uint64_t op = 0;
  while (ip < n) {
    uint8_t tag = in[ip++];
    uint32_t kind = tag & 3;
    if (kind == 0) {  // literal
      uint64_t len = (tag >> 2) + 1;
      if (len > 60) {
        uint32_t extra = (uint32_t)len - 60;
        if (ip + extra > n) return (uint64_t)-1;
        len = 0;
        for (uint32_t i = 0; i < extra; i++)
          len |= (uint64_t)in[ip + i] << (8 * i);
        len += 1;
        ip += extra;
      }
      if (ip + len > n || op + len > expect) return (uint64_t)-1;
      memcpy(out + op, in + ip, len);
      ip += len;
      op += len;
    } else {
      uint64_t len, offset;
      if (kind == 1) {
        if (ip >= n) return (uint64_t)-1;
        len = ((tag >> 2) & 0x7) + 4;
        offset = ((uint64_t)(tag >> 5) << 8) | in[ip++];
      } else if (kind == 2) {
        if (ip + 2 > n) return (uint64_t)-1;
        len = (tag >> 2) + 1;
        offset = in[ip] | ((uint64_t)in[ip + 1] << 8);
        ip += 2;
      } else {
        if (ip + 4 > n) return (uint64_t)-1;
        len = (tag >> 2) + 1;
        offset = load32(in + ip);
        ip += 4;
      }
      if (offset == 0 || offset > op || op + len > expect)
        return (uint64_t)-1;
      // overlapping copies are byte-serial by definition
      for (uint64_t i = 0; i < len; i++) out[op + i] = out[op + i - offset];
      op += len;
    }
  }
  return op == expect ? op : (uint64_t)-1;
}

}  // extern "C"

// ---- CRC32C (Castagnoli) --------------------------------------------------
// The snappy FRAMING format's chunk checksums (masked CRC32C) — needed
// for the spec's ssz_snappy req/resp streams.  Hardware _mm_crc32 when
// SSE4.2 is present, table fallback otherwise.

#include <cpuid.h>

extern "C" {

static uint32_t crc32c_table[256];
static bool crc32c_table_ready = false;

static void crc32c_init_table() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
    crc32c_table[i] = c;
  }
  crc32c_table_ready = true;
}

static bool crc32c_have_sse42() {
  unsigned eax, ebx, ecx, edx;
  if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
  return (ecx >> 20) & 1;  // SSE4.2
}

__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(uint32_t crc, const uint8_t* data, uint64_t n) {
  uint64_t i = 0;
  for (; i + 8 <= n; i += 8) {
    uint64_t v;
    __builtin_memcpy(&v, data + i, 8);
    crc = (uint32_t)__builtin_ia32_crc32di(crc, v);
  }
  for (; i < n; i++) crc = __builtin_ia32_crc32qi(crc, data[i]);
  return crc;
}

static uint32_t crc32c_sw(uint32_t crc, const uint8_t* data, uint64_t n) {
  if (!crc32c_table_ready) crc32c_init_table();
  for (uint64_t i = 0; i < n; i++)
    crc = crc32c_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return crc;
}

uint32_t teku_crc32c(const uint8_t* data, uint64_t n) {
  static int use_hw = -1;
  if (use_hw < 0) use_hw = crc32c_have_sse42() ? 1 : 0;
  uint32_t crc = 0xFFFFFFFFu;
  crc = use_hw ? crc32c_hw(crc, data, n) : crc32c_sw(crc, data, n);
  return crc ^ 0xFFFFFFFFu;
}

}  // extern "C"
