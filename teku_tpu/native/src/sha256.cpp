// SHA-256 compression: portable scalar core + x86 SHA-NI fast path.
//
// The native hashing layer behind SSZ merkleization (the role the JVM's
// SHA-256 intrinsics play for the reference's hash-tree-root; reference:
// infrastructure/crypto + the Sha256Benchmark surface).  Exposes ONE
// bulk primitive — hash_pairs over a contiguous buffer — because
// merkleization only ever hashes 64-byte concatenations of two nodes.

#include <cstdint>
#include <cstring>

#if defined(__x86_64__)
#include <immintrin.h>
#include <cpuid.h>
#endif

namespace {

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

constexpr uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                            0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }
inline uint32_t be32(const uint8_t* p) {
  return (uint32_t)p[0] << 24 | (uint32_t)p[1] << 16 | (uint32_t)p[2] << 8 |
         (uint32_t)p[3];
}
inline void put_be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}

void compress_scalar(uint32_t st[8], const uint8_t* block) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++) w[i] = be32(block + 4 * i);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = st[0], b = st[1], c = st[2], d = st[3];
  uint32_t e = st[4], f = st[5], g = st[6], h = st[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  st[0] += a; st[1] += b; st[2] += c; st[3] += d;
  st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

#if defined(__x86_64__)
bool cpu_has_sha() {
  unsigned int eax, ebx, ecx, edx;
  if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx)) return false;
  return (ebx >> 29) & 1;  // SHA extensions bit
}

// SHA-NI two-block compress of one 64-byte message with standard
// one-shot padding (the merkleize case: message length is exactly 64).
// Round scheduling follows the canonical SHA-NI pattern (public domain
// reference implementations by Intel/Walton).
__attribute__((target("sha,sse4.1")))
void compress_shani(uint32_t st[8], const uint8_t* block) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  __m128i STATE0 = _mm_loadu_si128((const __m128i*)&st[0]);  // a,b,c,d
  __m128i STATE1 = _mm_loadu_si128((const __m128i*)&st[4]);  // e,f,g,h
  // shuffle into the (CDAB / GHEF) order sha256rnds2 expects
  __m128i TMP = _mm_shuffle_epi32(STATE0, 0xB1);       // b,a,d,c
  STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);            // h,g,f,e -> f,e,h,g?
  STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);
  STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);
  const __m128i ABEF_SAVE = STATE0;
  const __m128i CDGH_SAVE = STATE1;

  __m128i MSG, MSG0, MSG1, MSG2, MSG3, TMP2;
#define QROUND(Ki, M)                                        \
  MSG = _mm_add_epi32(M, _mm_loadu_si128((const __m128i*)&K[Ki])); \
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);       \
  MSG = _mm_shuffle_epi32(MSG, 0x0E);                        \
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

  MSG0 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 0)), MASK);
  MSG1 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 16)), MASK);
  MSG2 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 32)), MASK);
  MSG3 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 48)), MASK);

  QROUND(0, MSG0);
  QROUND(4, MSG1);
  QROUND(8, MSG2);
  QROUND(12, MSG3);
  for (int i = 16; i < 64; i += 16) {
    MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);
    TMP2 = _mm_alignr_epi8(MSG3, MSG2, 4);
    MSG0 = _mm_add_epi32(MSG0, TMP2);
    MSG0 = _mm_sha256msg2_epu32(MSG0, MSG3);
    QROUND(i, MSG0);
    MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);
    TMP2 = _mm_alignr_epi8(MSG0, MSG3, 4);
    MSG1 = _mm_add_epi32(MSG1, TMP2);
    MSG1 = _mm_sha256msg2_epu32(MSG1, MSG0);
    QROUND(i + 4, MSG1);
    MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);
    TMP2 = _mm_alignr_epi8(MSG1, MSG0, 4);
    MSG2 = _mm_add_epi32(MSG2, TMP2);
    MSG2 = _mm_sha256msg2_epu32(MSG2, MSG1);
    QROUND(i + 8, MSG2);
    MSG3 = _mm_sha256msg1_epu32(MSG3, MSG0);
    TMP2 = _mm_alignr_epi8(MSG2, MSG1, 4);
    MSG3 = _mm_add_epi32(MSG3, TMP2);
    MSG3 = _mm_sha256msg2_epu32(MSG3, MSG2);
    QROUND(i + 12, MSG3);
  }
#undef QROUND

  STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
  STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);
  TMP = _mm_shuffle_epi32(STATE0, 0x1B);
  STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);
  STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0);
  STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);
  _mm_storeu_si128((__m128i*)&st[0], STATE0);
  _mm_storeu_si128((__m128i*)&st[4], STATE1);
}

bool g_use_shani = cpu_has_sha();
#else
bool g_use_shani = false;
void compress_shani(uint32_t*, const uint8_t*) {}
#endif

inline void compress(uint32_t st[8], const uint8_t* block) {
  if (g_use_shani)
    compress_shani(st, block);
  else
    compress_scalar(st, block);
}

// constant second block for a 64-byte message: 0x80 then zeros, with the
// 512-bit length in the last 8 bytes
const uint8_t PAD64[64] = {0x80, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                           0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                           0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                           0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                           0,    0, 0, 0, 0, 0, 0x02, 0x00};

}  // namespace

extern "C" {

// out[i] = sha256(in[64*i .. 64*i+63]) for i in [0, n)
void teku_hash_pairs(const uint8_t* in, uint64_t n, uint8_t* out) {
  for (uint64_t i = 0; i < n; i++) {
    uint32_t st[8];
    memcpy(st, H0, sizeof(st));
    compress(st, in + 64 * i);
    compress(st, PAD64);
    for (int j = 0; j < 8; j++) put_be32(out + 32 * i + 4 * j, st[j]);
  }
}

// general one-shot sha256 (tooling/tests)
void teku_sha256(const uint8_t* in, uint64_t len, uint8_t* out) {
  uint32_t st[8];
  memcpy(st, H0, sizeof(st));
  uint64_t off = 0;
  while (len - off >= 64) {
    compress(st, in + off);
    off += 64;
  }
  uint8_t last[128];
  uint64_t rem = len - off;
  memcpy(last, in + off, rem);
  last[rem] = 0x80;
  uint64_t padlen = (rem < 56) ? 64 : 128;
  memset(last + rem + 1, 0, padlen - rem - 1 - 8);
  uint64_t bits = len * 8;
  for (int j = 0; j < 8; j++)
    last[padlen - 1 - j] = (uint8_t)(bits >> (8 * j));
  compress(st, last);
  if (padlen == 128) compress(st, last + 64);
  for (int j = 0; j < 8; j++) put_be32(out + 4 * j, st[j]);
}

int teku_sha_uses_shani() { return g_use_shani ? 1 : 0; }

}  // extern "C"
