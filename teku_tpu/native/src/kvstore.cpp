// Embedded append-log KV engine with a memory-bounded index.
//
// The storage backend role RocksDB/LevelDB play for the reference
// (reference: storage/src/main/java/tech/pegasys/teku/storage/server/
// kvstore/ + rocksdbjni/leveldb-native deps in gradle/versions.gradle):
// a write-ahead append log, explicit flush (fsync), and compaction that
// rewrites the live set.  Record framing is CRC-checked so a torn tail
// write is truncated, not propagated.
//
// MEMORY MODEL: the in-memory index maps key -> (offset, length) of the
// value INSIDE the log; values themselves stay on disk and are read
// back on demand.  RSS is bounded by the live KEY set (an archive-mode
// chain where multi-megabyte states dominate the data keeps a flat
// footprint as the DB grows); the log replay on open rebuilds only the
// offset table, never materializes values.
//
// C ABI kept dumb-simple for ctypes: byte buffers + lengths, caller
// frees returned buffers via kv_free.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#ifndef _WIN32
#include <fcntl.h>
#include <unistd.h>
#endif

namespace {

uint32_t crc32_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32(const uint8_t* p, size_t n, uint32_t seed = 0) {
  crc_init();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = crc32_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct ValueRef {
  uint64_t off = 0;   // byte offset of the value bytes in the log
  uint32_t len = 0;
};

struct Store {
  std::string path;
  FILE* log = nullptr;     // append handle
  int read_fd = -1;        // independent read descriptor (pread)
  uint64_t end_off = 0;    // logical end of the log (append cursor)
  bool dirty = false;      // appended since last fflush
  bool broken = false;     // append desync: refuse further writes
  std::mutex mu;           // ctypes releases the GIL: REST executor
                           // threads read while the loop thread writes
  std::map<std::string, ValueRef> index;
};

constexpr uint8_t OP_PUT = 1;
constexpr uint8_t OP_DEL = 2;

// record: u8 op | u32 klen | u32 vlen | key | value | u32 crc(all prior)
bool append_record(Store* s, uint8_t op, const std::string& k,
                   const uint8_t* v, uint32_t vlen) {
  std::vector<uint8_t> buf;
  buf.reserve(9 + k.size() + vlen + 4);
  buf.push_back(op);
  uint32_t klen = (uint32_t)k.size();
  const uint8_t* kp = (const uint8_t*)&klen;
  const uint8_t* vp = (const uint8_t*)&vlen;
  buf.insert(buf.end(), kp, kp + 4);
  buf.insert(buf.end(), vp, vp + 4);
  buf.insert(buf.end(), k.begin(), k.end());
  if (vlen) buf.insert(buf.end(), v, v + vlen);
  uint32_t crc = crc32(buf.data(), buf.size());
  const uint8_t* cp = (const uint8_t*)&crc;
  buf.insert(buf.end(), cp, cp + 4);
  if (fwrite(buf.data(), 1, buf.size(), s->log) != buf.size()) {
    // a partial record would desync every future offset: try to cut
    // the torn tail; if that fails the handle is permanently
    // read-only (reads of already-indexed offsets stay valid)
    fflush(s->log);
#ifndef _WIN32
    if (ftruncate(fileno(s->log), (off_t)s->end_off) != 0)
      s->broken = true;
#else
    s->broken = true;
#endif
    return false;
  }
  s->end_off += buf.size();
  s->dirty = true;
  return true;
}

// replay into the offset index (values are skipped, not loaded);
// returns the byte offset of the last VALID record end
uint64_t replay(Store* s, FILE* f) {
  uint64_t good_end = 0;
  uint64_t pos = 0;
  for (;;) {
    uint8_t head[9];
    if (fread(head, 1, 9, f) != 9) break;
    uint8_t op = head[0];
    uint32_t klen, vlen;
    memcpy(&klen, head + 1, 4);
    memcpy(&vlen, head + 5, 4);
    if ((op != OP_PUT && op != OP_DEL) || klen > (1u << 30) ||
        vlen > (1u << 30))
      break;
    std::vector<uint8_t> body(klen + (size_t)vlen + 4);
    if (fread(body.data(), 1, body.size(), f) != body.size()) break;
    std::vector<uint8_t> all(head, head + 9);
    all.insert(all.end(), body.begin(), body.end() - 4);
    uint32_t want;
    memcpy(&want, body.data() + klen + vlen, 4);
    if (crc32(all.data(), all.size()) != want) break;  // torn tail
    std::string key((char*)body.data(), klen);
    if (op == OP_PUT) {
      ValueRef ref;
      ref.off = pos + 9 + klen;
      ref.len = vlen;
      s->index[key] = ref;
    } else {
      s->index.erase(key);
    }
    pos += 9 + body.size();
    good_end = pos;
  }
  return good_end;
}

bool read_value(Store* s, const ValueRef& ref, uint8_t* out) {
  // caller holds s->mu
  if (s->dirty) {           // buffered appends must be visible to reads
    fflush(s->log);
    s->dirty = false;
  }
#ifndef _WIN32
  size_t got = 0;
  while (got < ref.len) {
    ssize_t n = pread(s->read_fd, out + got, ref.len - got,
                      (off_t)(ref.off + got));
    if (n <= 0) return false;
    got += (size_t)n;
  }
  return true;
#else
  return false;
#endif
}

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  Store* s = new Store();
  s->path = path;
  FILE* f = fopen(path, "rb");
  if (f) {
    uint64_t good = replay(s, f);
    fseek(f, 0, SEEK_END);
    uint64_t full = (uint64_t)ftell(f);
    fclose(f);
    // truncate a torn tail so the next append starts clean
    if (good < full) {
      if (truncate(path, (long)good) != 0) {
        delete s;
        return nullptr;
      }
    }
    s->end_off = good;
  }
  s->log = fopen(path, "ab");
  if (!s->log) {
    delete s;
    return nullptr;
  }
#ifndef _WIN32
  s->read_fd = open(path, O_RDONLY);
#endif
  if (s->read_fd < 0) {
    fclose(s->log);
    delete s;
    return nullptr;
  }
  return s;
}

int kv_put(void* h, const uint8_t* k, uint32_t klen, const uint8_t* v,
           uint32_t vlen) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> lock(s->mu);
  if (s->broken) return -1;
  std::string key((const char*)k, klen);
  ValueRef ref;
  ref.off = s->end_off + 9 + klen;
  ref.len = vlen;
  if (!append_record(s, OP_PUT, key, v, vlen)) return -1;
  s->index[key] = ref;
  return 0;
}

int kv_del(void* h, const uint8_t* k, uint32_t klen) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> lock(s->mu);
  if (s->broken) return -1;
  std::string key((const char*)k, klen);
  if (s->index.find(key) == s->index.end()) return 1;  // absent
  if (!append_record(s, OP_DEL, key, nullptr, 0)) return -1;
  s->index.erase(key);
  return 0;
}

// returns 0 + malloc'd copy in *out; 1 if absent; -1 on read error
int kv_get(void* h, const uint8_t* k, uint32_t klen, uint8_t** out,
           uint32_t* out_len) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->index.find(std::string((const char*)k, klen));
  if (it == s->index.end()) return 1;
  *out_len = it->second.len;
  *out = (uint8_t*)malloc(it->second.len ? it->second.len : 1);
  if (!read_value(s, it->second, *out)) {
    free(*out);
    *out = nullptr;
    return -1;
  }
  return 0;
}

void kv_free(uint8_t* p) { free(p); }

uint64_t kv_count(void* h) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> lock(s->mu);
  return s->index.size();
}

int kv_flush(void* h) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> lock(s->mu);
  if (fflush(s->log) != 0) return -1;
  s->dirty = false;
#ifndef _WIN32
  if (fsync(fileno(s->log)) != 0) return -1;
#endif
  return 0;
}

// all keys with the given prefix, concatenated as u32len|key entries
int kv_keys(void* h, const uint8_t* prefix, uint32_t plen, uint8_t** out,
            uint64_t* out_len) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> lock(s->mu);
  std::string pre((const char*)prefix, plen);
  std::vector<uint8_t> buf;
  for (auto it = s->index.lower_bound(pre); it != s->index.end(); ++it) {
    if (it->first.compare(0, pre.size(), pre) != 0) break;
    uint32_t n = (uint32_t)it->first.size();
    const uint8_t* np = (const uint8_t*)&n;
    buf.insert(buf.end(), np, np + 4);
    buf.insert(buf.end(), it->first.begin(), it->first.end());
  }
  *out_len = buf.size();
  *out = (uint8_t*)malloc(buf.size() ? buf.size() : 1);
  memcpy(*out, buf.data(), buf.size());
  return 0;
}

// rewrite only the live set (drops overwritten/deleted records);
// values stream through a bounded buffer, never all in memory at once
int kv_compact(void* h) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> lock(s->mu);
  if (s->broken) return -1;
  if (s->dirty) {
    fflush(s->log);
    s->dirty = false;
  }
  std::string tmp = s->path + ".compact";
  Store fresh;
  fresh.path = tmp;
  fresh.log = fopen(tmp.c_str(), "wb");
  if (!fresh.log) return -1;
  std::map<std::string, ValueRef> new_index;
  std::vector<uint8_t> val;
  for (auto& kvp : s->index) {
    val.resize(kvp.second.len);
    if (!read_value(s, kvp.second, val.data())) {
      fclose(fresh.log);
      remove(tmp.c_str());
      return -1;
    }
    ValueRef ref;
    ref.off = fresh.end_off + 9 + kvp.first.size();
    ref.len = kvp.second.len;
    if (!append_record(&fresh, OP_PUT, kvp.first, val.data(),
                       kvp.second.len)) {
      fclose(fresh.log);
      remove(tmp.c_str());
      return -1;
    }
    new_index[kvp.first] = ref;
  }
  fflush(fresh.log);
#ifndef _WIN32
  fsync(fileno(fresh.log));
#endif
  fclose(fresh.log);
  if (rename(tmp.c_str(), s->path.c_str()) != 0) {
    // ORIGINAL file is untouched: the open handles stay valid and the
    // store keeps serving from the uncompacted log
    remove(tmp.c_str());
    return -1;
  }
  // the old handles now reference the unlinked inode: swap them for
  // the compacted file before anything else can fail
  fclose(s->log);
#ifndef _WIN32
  close(s->read_fd);
  s->read_fd = open(s->path.c_str(), O_RDONLY);
#endif
  s->log = fopen(s->path.c_str(), "ab");
  if (!s->log || s->read_fd < 0) {
    s->broken = true;                 // cannot write; reads unsafe too
    return -1;
  }
  s->index = std::move(new_index);
  s->end_off = fresh.end_off;
  s->dirty = false;
  return 0;
}

void kv_close(void* h) {
  Store* s = (Store*)h;
  {
    std::lock_guard<std::mutex> lock(s->mu);
    if (s->log) {
      fflush(s->log);
      fclose(s->log);
    }
    if (s->read_fd >= 0) close(s->read_fd);
  }
  delete s;
}

}  // extern "C"
