// Embedded append-log KV engine with an in-memory index.
//
// The storage backend role RocksDB/LevelDB play for the reference
// (reference: storage/src/main/java/tech/pegasys/teku/storage/server/
// kvstore/ + rocksdbjni/leveldb-native deps in gradle/versions.gradle):
// a write-ahead append log replayed into a std::map on open, explicit
// flush (fsync), and compaction that rewrites the live set.  Record
// framing is CRC-checked so a torn tail write is truncated, not
// propagated.
//
// C ABI kept dumb-simple for ctypes: byte buffers + lengths, caller
// frees returned buffers via kv_free.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#ifndef _WIN32
#include <unistd.h>
#endif

namespace {

uint32_t crc32_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32(const uint8_t* p, size_t n, uint32_t seed = 0) {
  crc_init();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = crc32_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Store {
  std::string path;
  FILE* log = nullptr;
  std::map<std::string, std::string> index;
};

constexpr uint8_t OP_PUT = 1;
constexpr uint8_t OP_DEL = 2;

// record: u8 op | u32 klen | u32 vlen | key | value | u32 crc(all prior)
bool append_record(Store* s, uint8_t op, const std::string& k,
                   const std::string& v) {
  std::vector<uint8_t> buf;
  buf.reserve(9 + k.size() + v.size() + 4);
  buf.push_back(op);
  uint32_t klen = (uint32_t)k.size(), vlen = (uint32_t)v.size();
  const uint8_t* kp = (const uint8_t*)&klen;
  const uint8_t* vp = (const uint8_t*)&vlen;
  buf.insert(buf.end(), kp, kp + 4);
  buf.insert(buf.end(), vp, vp + 4);
  buf.insert(buf.end(), k.begin(), k.end());
  buf.insert(buf.end(), v.begin(), v.end());
  uint32_t crc = crc32(buf.data(), buf.size());
  const uint8_t* cp = (const uint8_t*)&crc;
  buf.insert(buf.end(), cp, cp + 4);
  return fwrite(buf.data(), 1, buf.size(), s->log) == buf.size();
}

// replay; returns the byte offset of the last VALID record end
long replay(Store* s, FILE* f) {
  long good_end = 0;
  for (;;) {
    uint8_t head[9];
    if (fread(head, 1, 9, f) != 9) break;
    uint8_t op = head[0];
    uint32_t klen, vlen;
    memcpy(&klen, head + 1, 4);
    memcpy(&vlen, head + 5, 4);
    if ((op != OP_PUT && op != OP_DEL) || klen > (1u << 30) ||
        vlen > (1u << 30))
      break;
    std::vector<uint8_t> body(klen + (size_t)vlen + 4);
    if (fread(body.data(), 1, body.size(), f) != body.size()) break;
    std::vector<uint8_t> all(head, head + 9);
    all.insert(all.end(), body.begin(), body.end() - 4);
    uint32_t want;
    memcpy(&want, body.data() + klen + vlen, 4);
    if (crc32(all.data(), all.size()) != want) break;  // torn tail
    std::string key((char*)body.data(), klen);
    if (op == OP_PUT)
      s->index[key] = std::string((char*)body.data() + klen, vlen);
    else
      s->index.erase(key);
    good_end = ftell(f);
  }
  return good_end;
}

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  Store* s = new Store();
  s->path = path;
  FILE* f = fopen(path, "rb");
  if (f) {
    long good = replay(s, f);
    fclose(f);
    // truncate a torn tail so the next append starts clean
    long full;
    FILE* probe = fopen(path, "rb");
    fseek(probe, 0, SEEK_END);
    full = ftell(probe);
    fclose(probe);
    if (good < full) {
      if (truncate(path, good) != 0) {
        delete s;
        return nullptr;
      }
    }
  }
  s->log = fopen(path, "ab");
  if (!s->log) {
    delete s;
    return nullptr;
  }
  return s;
}

int kv_put(void* h, const uint8_t* k, uint32_t klen, const uint8_t* v,
           uint32_t vlen) {
  Store* s = (Store*)h;
  std::string key((const char*)k, klen), val((const char*)v, vlen);
  if (!append_record(s, OP_PUT, key, val)) return -1;
  s->index[key] = std::move(val);
  return 0;
}

int kv_del(void* h, const uint8_t* k, uint32_t klen) {
  Store* s = (Store*)h;
  std::string key((const char*)k, klen);
  if (s->index.find(key) == s->index.end()) return 1;  // absent
  if (!append_record(s, OP_DEL, key, "")) return -1;
  s->index.erase(key);
  return 0;
}

// returns 0 + malloc'd copy in *out; 1 if absent
int kv_get(void* h, const uint8_t* k, uint32_t klen, uint8_t** out,
           uint32_t* out_len) {
  Store* s = (Store*)h;
  auto it = s->index.find(std::string((const char*)k, klen));
  if (it == s->index.end()) return 1;
  *out_len = (uint32_t)it->second.size();
  *out = (uint8_t*)malloc(it->second.size() ? it->second.size() : 1);
  memcpy(*out, it->second.data(), it->second.size());
  return 0;
}

void kv_free(uint8_t* p) { free(p); }

uint64_t kv_count(void* h) { return ((Store*)h)->index.size(); }

int kv_flush(void* h) {
  Store* s = (Store*)h;
  if (fflush(s->log) != 0) return -1;
#ifndef _WIN32
  if (fsync(fileno(s->log)) != 0) return -1;
#endif
  return 0;
}

// all keys with the given prefix, concatenated as u32len|key entries
int kv_keys(void* h, const uint8_t* prefix, uint32_t plen, uint8_t** out,
            uint64_t* out_len) {
  Store* s = (Store*)h;
  std::string pre((const char*)prefix, plen);
  std::vector<uint8_t> buf;
  for (auto it = s->index.lower_bound(pre); it != s->index.end(); ++it) {
    if (it->first.compare(0, pre.size(), pre) != 0) break;
    uint32_t n = (uint32_t)it->first.size();
    const uint8_t* np = (const uint8_t*)&n;
    buf.insert(buf.end(), np, np + 4);
    buf.insert(buf.end(), it->first.begin(), it->first.end());
  }
  *out_len = buf.size();
  *out = (uint8_t*)malloc(buf.size() ? buf.size() : 1);
  memcpy(*out, buf.data(), buf.size());
  return 0;
}

// rewrite only the live set (drops overwritten/deleted records)
int kv_compact(void* h) {
  Store* s = (Store*)h;
  std::string tmp = s->path + ".compact";
  FILE* old = s->log;
  Store fresh;
  fresh.path = tmp;
  fresh.log = fopen(tmp.c_str(), "wb");
  if (!fresh.log) return -1;
  for (auto& kvp : s->index)
    if (!append_record(&fresh, OP_PUT, kvp.first, kvp.second)) {
      fclose(fresh.log);
      return -1;
    }
  fflush(fresh.log);
#ifndef _WIN32
  fsync(fileno(fresh.log));
#endif
  fclose(fresh.log);
  fclose(old);
  if (rename(tmp.c_str(), s->path.c_str()) != 0) return -1;
  s->log = fopen(s->path.c_str(), "ab");
  return s->log ? 0 : -1;
}

void kv_close(void* h) {
  Store* s = (Store*)h;
  if (s->log) {
    fflush(s->log);
    fclose(s->log);
  }
  delete s;
}

}  // extern "C"
